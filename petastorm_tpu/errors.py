"""Exception types for petastorm_tpu.

Parity: reference ``petastorm/errors.py`` (NoDataAvailableError) plus decode
errors from ``petastorm/utils.py:50``; the robustness layer (worker
supervision + poison row-group quarantine) adds its own failure types.

Exception hierarchy::

    PetastormTpuError                     base class for everything we raise
    ├── NoDataAvailableError              sharding/filtering left no row-groups
    ├── SchemaError                       schema definition/inference problems
    ├── DecodeFieldError                  a field value failed codec decode
    ├── WorkerLostError                   a pool worker process died and the
    │                                     respawn budget is exhausted
    ├── RowGroupQuarantinedError          decode/IO failures exceeded the
    │                                     reader's ``error_budget`` (or a
    │                                     quarantine arrived with no budget
    │                                     configured)
    ├── PipelineStallError                the health watchdog diagnosed a
    │                                     stalled stage and soft recovery
    │                                     did not clear it (carries the
    │                                     full diagnosis: classification,
    │                                     beat table, thread stacks)
    ├── HostMemoryExceededError           the memory governor's pressure
    │                                     ladder breached its budget after
    │                                     degradation; carries the per-pool
    │                                     byte ranking and the flight-dump
    │                                     path (we die WITH a diagnosis,
    │                                     before the kernel OOM killer)
    ├── CorruptChunkError                 a decoded-chunk store entry (or
    │                                     raw-layout disk-cache blob)
    │                                     failed structural/checksum
    │                                     validation; the entry is
    │                                     quarantined and refilled
    └── PodAbortError                     a pod peer died/desynced; defined
                                          in ``parallel/pod_guard.py``

Related errors defined elsewhere (not under the base class because they
pre-date it or mirror stdlib types): ``hdfs.HdfsConnectError`` (IOError),
``hdfs.MaxFailoversExceeded`` (RuntimeError),
``retry.RetryDeadlineExceeded``, and the pool-protocol sentinels
``workers.EmptyResultError`` / ``workers.TimeoutWaitingForResultError``.

Failure-handling contract (see ``docs/failure_model.rst``): transient
filesystem errors retry (``retry.RetryPolicy``); a dead worker process is
respawned within a restart budget and its in-flight row-groups re-ventilated
(``WorkerLostError`` past the budget); a row-group that keeps failing to
decode is quarantined when the reader opts in via ``error_budget``
(``RowGroupQuarantinedError`` once the budget is spent).
"""


class PetastormTpuError(Exception):
    """Base class for all petastorm_tpu errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when sharding/filtering leaves a reader with no row-groups.

    Parity: reference ``petastorm/errors.py:16`` raised at ``reader.py:495-497``.
    """


class DecodeFieldError(PetastormTpuError):
    """Raised when a field value cannot be decoded by its codec.

    Parity: reference ``petastorm/utils.py:50``.

    ``native_error`` (optional) carries the native codec's own error string
    (``native.image.decode_error_message``) when the failure came out of
    the C++ batch decoder — quarantine records surface it so a poisoned
    image reads as e.g. ``'not a JPEG or PNG stream'`` in
    ``Reader.diagnostics()['quarantined_rowgroups']`` instead of a bare
    exception repr.
    """

    def __init__(self, message, native_error=None):
        super().__init__(message)
        self.native_error = native_error


class SchemaError(PetastormTpuError):
    """Raised for schema definition / inference problems."""


class WorkerLostError(PetastormTpuError):
    """A worker process died mid-epoch and the pool's restart budget is
    exhausted (or respawn itself failed). The message carries which workers
    died, their exit codes, and the row-group items that were in flight."""


class RowGroupQuarantinedError(PetastormTpuError):
    """Poison row-group failures exceeded the reader's ``error_budget``.

    ``quarantined`` holds the per-row-group records accumulated before the
    budget ran out (also available from ``Reader.diagnostics()`` while the
    budget holds).
    """

    def __init__(self, message, quarantined=None):
        super(RowGroupQuarantinedError, self).__init__(message)
        self.quarantined = list(quarantined or [])


class PipelineStallError(PetastormTpuError):
    """The health watchdog (``petastorm_tpu.health``) diagnosed a stalled
    pipeline stage and escalating recovery did not clear it.

    The message names the stalled stage and classification and embeds the
    all-thread stack dump; ``diagnosis`` holds the structured report
    (classification, stage, detail, last-beat table, probe snapshots,
    stacks) for programmatic triage."""

    def __init__(self, message, diagnosis=None):
        super(PipelineStallError, self).__init__(message)
        self.diagnosis = diagnosis or {}


class HostMemoryExceededError(PetastormTpuError):
    """The host memory governor (``petastorm_tpu.membudget``) breached its
    byte budget after walking the whole degradation ladder (advisory ->
    degrade -> shed). Raised *instead of* letting the kernel OOM killer
    SIGKILL the process: the message names the top byte-holding pool and
    the flight-dump directory.

    ``ranking`` is the per-pool byte ranking (``[{'pool', 'nbytes'}, ...]``,
    biggest first); ``flight_dump`` the dump path (``None`` when even the
    best-effort dump failed); ``budget``/``accounted`` the bytes that
    tripped the breach."""

    def __init__(self, message, budget=None, accounted=None, ranking=None,
                 flight_dump=None):
        super(HostMemoryExceededError, self).__init__(message)
        self.budget = budget
        self.accounted = accounted
        self.ranking = list(ranking or [])
        self.flight_dump = flight_dump


class ServerOverloaded(PetastormTpuError):
    """Every data-service server refused this consumer's attach — at its
    ``max_consumers`` admission capacity, or draining/drained
    (``data_service.DataServer``). Typed so orchestrators can distinguish
    "scale the decode tier / retry elsewhere / wait out the drain" from a
    genuine failure. ``endpoint`` names a refusing rpc endpoint;
    ``reason`` is the server's refusal label (``overloaded`` /
    ``draining`` / ``drained``)."""

    def __init__(self, message, endpoint=None, reason=None):
        super(ServerOverloaded, self).__init__(message)
        self.endpoint = endpoint
        self.reason = reason


class CorruptChunkError(PetastormTpuError):
    """A persisted decoded chunk (``chunk_store.DecodedChunkStore`` entry
    or ``LocalDiskCache`` raw-layout blob) failed magic/structure/CRC32
    validation. Callers quarantine the bytes and refill by re-decode;
    this error itself never crosses ``cache.get`` (a *refill* failure
    surfaces as the decode error it is, flowing into the ``error_budget``
    quarantine machinery)."""


#: Failure classes a worker may *quarantine* (skip-and-record the row-group)
#: instead of crashing the epoch, when the reader opted in via
#: ``error_budget``. Deliberately narrow: data/IO problems qualify;
#: programming errors (TypeError, KeyError, ...) always surface.
QUARANTINE_EXCEPTION_TYPES = (DecodeFieldError, IOError, OSError)
