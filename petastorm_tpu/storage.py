"""Parquet store discovery: files, hive partitions, row-group pieces, metadata.

This replaces the reference's reliance on the legacy
``pyarrow.parquet.ParquetDataset`` pieces API (``reader.py:357``,
``etl/dataset_metadata.py:231-336``) with a small self-contained layer over
fsspec + ``pq.ParquetFile``, because modern pyarrow removed the legacy dataset
pieces. The unit of IO is still the **Parquet row-group**
(:class:`RowGroupPiece`).

Row-group listing strategies (parity with reference
``etl/dataset_metadata.py:231-336``):
  1. ``_metadata`` summary file (one footer read for the whole store);
  2. the ``{file -> num_row_groups}`` JSON index stored in
     ``_common_metadata`` by our writer;
  3. parallel per-file footer reads as a fallback.
"""

import json
import logging
import os
import posixpath
from concurrent.futures import ThreadPoolExecutor

import pyarrow.parquet as pq

from petastorm_tpu.fs import FilesystemResolver

logger = logging.getLogger(__name__)

# _common_metadata keys (JSON payloads, not pickle — see unischema.py docstring)
UNISCHEMA_KEY = b'petastorm_tpu.unischema.v1'
NUM_ROW_GROUPS_KEY = b'petastorm_tpu.num_row_groups_per_file.v1'
ROWGROUP_INDEX_KEY = b'petastorm_tpu.rowgroups_index.v1'

_METADATA_FILE = '_metadata'
_COMMON_METADATA_FILE = '_common_metadata'


class RowGroupPiece(object):
    """One row-group of one Parquet file — the unit of reader work."""

    __slots__ = ('path', 'row_group', 'partition_values', 'num_rows')

    def __init__(self, path, row_group, partition_values=None, num_rows=None):
        self.path = path
        self.row_group = row_group
        self.partition_values = partition_values or {}
        self.num_rows = num_rows

    def __repr__(self):
        return 'RowGroupPiece({!r}, rg={}, partitions={}, rows={})'.format(
            self.path, self.row_group, self.partition_values, self.num_rows)

    def __eq__(self, other):
        return (isinstance(other, RowGroupPiece) and self.path == other.path
                and self.row_group == other.row_group)

    def __hash__(self):
        return hash((self.path, self.row_group))


def _parse_partition_values(root, file_path):
    """Extract hive-style ``key=value`` directory components."""
    rel = posixpath.relpath(file_path, root)
    values = {}
    for segment in rel.split('/')[:-1]:
        if '=' in segment:
            key, _, value = segment.partition('=')
            values[key] = value
    return values


def _coerce_partition_value(value):
    """Hive partition values are strings on disk; try int then float."""
    for cast in (int, float):
        try:
            return cast(value)
        except (TypeError, ValueError):
            continue
    return value


class ParquetStore(object):
    """A discovered Parquet dataset: file list, partitions, metadata access."""

    def __init__(self, dataset_url, storage_options=None, filesystem=None, path=None):
        self.storage_options = dict(storage_options or {})
        if filesystem is not None:
            self.fs = filesystem
            self.path = path if path is not None else dataset_url
            self.url = dataset_url
        else:
            resolver = FilesystemResolver(dataset_url, storage_options)
            self.fs = resolver.filesystem()
            self.path = resolver.get_dataset_path()
            self.url = resolver.dataset_url
        self._files = None
        self._common_metadata = None
        self._common_metadata_loaded = False

    # --- file discovery ---------------------------------------------------

    @property
    def files(self):
        """Sorted data file paths (deterministic across hosts — parity with
        the sorted piece order at ``etl/dataset_metadata.py:263-265``)."""
        if self._files is None:
            if not self.fs.exists(self.path):
                raise IOError('Dataset path does not exist: {}'.format(self.url))
            if self.fs.isfile(self.path):
                self._files = [self.path]
            else:
                found = self.fs.find(self.path)
                self._files = sorted(
                    f for f in found
                    if not os.path.basename(f).startswith(('_', '.')) and not f.endswith('.crc'))
        return self._files

    @property
    def partition_names(self):
        names = []
        for f in self.files:
            for key in _parse_partition_values(self.path, f):
                if key not in names:
                    names.append(key)
        return names

    def partition_values_for(self, file_path):
        raw = _parse_partition_values(self.path, file_path)
        return {k: _coerce_partition_value(v) for k, v in raw.items()}

    # --- metadata ---------------------------------------------------------

    def _metadata_path(self, name):
        return posixpath.join(self.path, name)

    def read_common_metadata(self):
        """Key-value metadata dict from ``_common_metadata`` (or None)."""
        if not self._common_metadata_loaded:
            self._common_metadata_loaded = True
            target = self._metadata_path(_COMMON_METADATA_FILE)
            if self.fs.exists(target):
                with self.fs.open(target, 'rb') as f:
                    schema = pq.read_schema(f)
                self._common_metadata = dict(schema.metadata or {})
            else:
                self._common_metadata = None
        return self._common_metadata

    def write_common_metadata(self, arrow_schema, extra_metadata):
        """Write/update ``_common_metadata`` with ``extra_metadata`` key-values.

        Parity: reference ``petastorm/utils.py:90-134``
        (``add_to_dataset_metadata``).
        """
        existing = self.read_common_metadata() or {}
        merged = dict(existing)
        for key, value in extra_metadata.items():
            key = key if isinstance(key, bytes) else key.encode('utf-8')
            value = value if isinstance(value, bytes) else value.encode('utf-8')
            merged[key] = value
        schema = arrow_schema.with_metadata(merged)
        target = self._metadata_path(_COMMON_METADATA_FILE)
        with self.fs.open(target, 'wb') as f:
            pq.write_metadata(schema, f)
        self._common_metadata = merged
        self._common_metadata_loaded = True
        crc = self._metadata_path('.' + _COMMON_METADATA_FILE + '.crc')
        if self.fs.exists(crc):  # stale checksum removal, utils.py:128-133
            self.fs.rm(crc)

    def common_metadata_value(self, key, default=None):
        md = self.read_common_metadata()
        if md is None:
            return default
        return md.get(key, default)

    def read_arrow_schema(self):
        """Arrow schema of the data files (first file's footer)."""
        target = self._metadata_path(_COMMON_METADATA_FILE)
        if self.fs.exists(target):
            with self.fs.open(target, 'rb') as f:
                schema = pq.read_schema(f)
            if schema.names:
                return schema
        with self.fs.open(self.files[0], 'rb') as f:
            return pq.read_schema(f)

    # --- row-group listing ------------------------------------------------

    def row_groups(self, max_footer_workers=10):
        """List all :class:`RowGroupPiece` using the fastest strategy available."""
        pieces = self._row_groups_from_summary_metadata()
        if pieces is None:
            pieces = self._row_groups_from_json_index()
        if pieces is None:
            pieces = self._row_groups_from_footers(max_footer_workers)
        return pieces

    def _row_groups_from_summary_metadata(self):
        """Strategy 1: single ``_metadata`` summary footer
        (parity: ``etl/dataset_metadata.py:279-312``)."""
        target = self._metadata_path(_METADATA_FILE)
        if not self.fs.exists(target):
            return None
        with self.fs.open(target, 'rb') as f:
            metadata = pq.read_metadata(f)
        per_file = {}
        for i in range(metadata.num_row_groups):
            rg = metadata.row_group(i)
            file_path = rg.column(0).file_path
            if not file_path:
                return None
            full = posixpath.join(self.path, file_path)
            per_file.setdefault(full, []).append(rg.num_rows)
        pieces = []
        for full in sorted(per_file):
            partitions = self.partition_values_for(full)
            for idx, num_rows in enumerate(per_file[full]):
                pieces.append(RowGroupPiece(full, idx, partitions, num_rows))
        return pieces

    def _row_groups_from_json_index(self):
        """Strategy 2: ``{relative_file -> num_row_groups}`` JSON in
        ``_common_metadata`` (parity: ``etl/dataset_metadata.py:246-273``)."""
        blob = self.common_metadata_value(NUM_ROW_GROUPS_KEY)
        if blob is None:
            # Reference-petastorm stores keep the same JSON under a legacy key
            # (reference etl/dataset_metadata.py:34).
            from petastorm_tpu.etl.legacy import LEGACY_NUM_ROW_GROUPS_KEY
            blob = self.common_metadata_value(LEGACY_NUM_ROW_GROUPS_KEY)
        if blob is None:
            return None
        counts = json.loads(blob.decode('utf-8'))
        pieces = []
        file_set = set(self.files)
        for rel in sorted(counts):
            full = posixpath.join(self.path, rel)
            if full not in file_set:
                logger.warning('Row-group index mentions missing file %s; falling back to footers', rel)
                return None
            partitions = self.partition_values_for(full)
            for idx in range(counts[rel]):
                pieces.append(RowGroupPiece(full, idx, partitions))
        return pieces

    def _row_groups_from_footers(self, max_workers):
        """Strategy 3: read every file footer, in parallel
        (parity: ``etl/dataset_metadata.py:323-336``)."""
        def footer(path):
            with self.fs.open(path, 'rb') as f:
                md = pq.read_metadata(f)
            return path, [md.row_group(i).num_rows for i in range(md.num_row_groups)]

        files = self.files
        results = {}
        if len(files) == 1:
            path, rows = footer(files[0])
            results[path] = rows
        else:
            with ThreadPoolExecutor(max_workers=min(max_workers, max(1, len(files)))) as pool:
                for path, rows in pool.map(footer, files):
                    results[path] = rows
        pieces = []
        for path in sorted(results):
            partitions = self.partition_values_for(path)
            for idx, num_rows in enumerate(results[path]):
                pieces.append(RowGroupPiece(path, idx, partitions, num_rows))
        return pieces

    def num_row_groups_per_file(self):
        """``{relative_path: count}`` for the JSON index."""
        counts = {}
        for piece in self.row_groups():
            rel = posixpath.relpath(piece.path, self.path)
            counts[rel] = counts.get(rel, 0) + 1
        return counts

    def open_file(self, path):
        return self.fs.open(path, 'rb')
