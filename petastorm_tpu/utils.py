"""Small shared utilities.

Parity: reference ``petastorm/utils.py:30-47`` (``run_in_subprocess``). The
reference's other utils live elsewhere here: ``decode_row`` ->
``unischema.decode_rows``, ``add_to_dataset_metadata`` ->
``storage.ParquetStore.write_common_metadata``.
"""


def run_in_subprocess(func, *args, **kwargs):
    """Run ``func(*args, **kwargs)`` in a one-shot subprocess and return its
    result — isolates memory leaks / library state from the calling process
    (the reference uses it so pyarrow allocations don't accumulate in tests
    and benchmarks).
    """
    from multiprocessing import Pool

    with Pool(1) as pool:
        return pool.apply(func, args, kwargs)
