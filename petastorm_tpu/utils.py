"""Small shared utilities.

Parity: reference ``petastorm/utils.py:30-47`` (``run_in_subprocess``). The
reference's other utils live elsewhere here: ``decode_row`` ->
``unischema.decode_rows``, ``add_to_dataset_metadata`` ->
``storage.ParquetStore.write_common_metadata``.
"""


def cached_namedtuple(cache, type_name, names):
    """Namedtuple type for ``names``, memoized in the caller's ``cache`` dict.

    Consumers that assemble batches from dict payloads (``JaxLoader``,
    ``RemoteReader``) must hand out the SAME type per field set — type
    equality is what lets downstream code (e.g. ``tf.data`` structure
    checks) treat consecutive batches as one structure.
    """
    nt = cache.get(names)
    if nt is None:
        from collections import namedtuple
        nt = namedtuple(type_name, names)
        cache[names] = nt
    return nt


def run_in_subprocess(func, *args, **kwargs):
    """Run ``func(*args, **kwargs)`` in a one-shot subprocess and return its
    result — isolates memory leaks / library state from the calling process
    (the reference uses it so pyarrow allocations don't accumulate in tests
    and benchmarks).
    """
    from multiprocessing import Pool

    with Pool(1) as pool:
        return pool.apply(func, args, kwargs)
