"""Small shared utilities.

Parity: reference ``petastorm/utils.py:30-47`` (``run_in_subprocess``). The
reference's other utils live elsewhere here: ``decode_row`` ->
``unischema.decode_rows``, ``add_to_dataset_metadata`` ->
``storage.ParquetStore.write_common_metadata``.
"""


def drain_queue(bounded_queue, buffer, max_items):
    """Move up to ``max_items`` ready items from a ``queue.Queue`` into a
    consumer-local ``buffer`` (deque) under ONE mutex acquisition — the
    batched-pop primitive behind the worker pool's result handoff
    (``ThreadPool._pop_result``; a per-item ``Queue.get`` costs a lock
    round trip each, and the warm-cache chunk rate is queue-pop bound,
    PROFILE_r05 §2). The cap matters: every drained slot is capacity the
    producers refill, so callers size it to bound how far undelivered
    items may overshoot the queue's nominal depth. Producers blocked on
    the bounded put are woken for the freed capacity. Returns the number
    of items moved.

    NOT used by the JaxLoader consumer: its drain must keep staged device
    batches within the ``prefetch`` bound, so it shrinks the queue's live
    ``maxsize`` by the drained count and skips the wakeup — see
    ``JaxLoader.__next__``."""
    with bounded_queue.mutex:
        take = min(len(bounded_queue.queue), max_items)
        for _ in range(take):
            buffer.append(bounded_queue.queue.popleft())
        if take > 0:
            bounded_queue.not_full.notify_all()
    return take


def cached_namedtuple(cache, type_name, names):
    """Namedtuple type for ``names``, memoized in the caller's ``cache`` dict.

    Consumers that assemble batches from dict payloads (``JaxLoader``,
    ``RemoteReader``) must hand out the SAME type per field set — type
    equality is what lets downstream code (e.g. ``tf.data`` structure
    checks) treat consecutive batches as one structure.
    """
    nt = cache.get(names)
    if nt is None:
        from collections import namedtuple
        nt = namedtuple(type_name, names)
        cache[names] = nt
    return nt


def honor_jax_platform_request():
    """Pin jax to CPU when ``JAX_PLATFORMS`` asks for it FIRST.

    A TPU PJRT plugin registered from a ``sitecustomize`` may call
    ``jax.config.update('jax_platforms', ...)``, which takes precedence
    over the ``JAX_PLATFORMS`` env var — an explicit ``JAX_PLATFORMS=cpu``
    then silently still initializes the accelerator backend (and on a
    wedged tunnel, blocks for minutes). CLIs and examples call this before
    their first jax operation so a cpu-first request is honored the way
    ``bench.py`` and ``__graft_entry__`` honor it. A request like
    ``tpu,cpu`` (accelerator with cpu fallback) is left alone.
    """
    import os
    if os.environ.get('JAX_PLATFORMS', '').split(',')[0].strip() == 'cpu':
        import jax
        jax.config.update('jax_platforms', 'cpu')


def run_in_subprocess(func, *args, **kwargs):
    """Run ``func(*args, **kwargs)`` in a one-shot subprocess and return its
    result — isolates memory leaks / library state from the calling process
    (the reference uses it so pyarrow allocations don't accumulate in tests
    and benchmarks).
    """
    from multiprocessing import Pool

    with Pool(1) as pool:
        return pool.apply(func, args, kwargs)
