"""Columnar row-group worker: keeps data as Arrow tables end to end.

Parity: reference ``petastorm/arrow_reader_worker.py`` — same per-row-group
flow as the dict worker but columnar: pandas-vectorized predicate (``:212``),
pandas-based TransformSpec (``:163-178``), unrequested partition columns
dropped (``:249-255``); the queue reader converts Arrow columns to numpy and
vstacks fixed-length list columns (``:39-79``); ``batched_output=True``
(``:36-37``); no ngram support (``:97-98``).

This is the TPU hot path: batched columnar decode feeds
``jax_loader`` with whole-row-group numpy blocks for zero-copy
``device_put`` staging.
"""

import hashlib

import numpy as np
import pyarrow as pa

from petastorm_tpu.checkpoint import DeferredRowAccounting, chunk_key
from petastorm_tpu.determinism import ResequencedReads
from petastorm_tpu.workers.rowgroup_worker_base import (RowGroupWorkerBase,
                                                        chunk_row_permutation,
                                                        compute_row_slice)


class ArrowWorker(RowGroupWorkerBase):
    """Same args dict as PyDictWorker (see its docstring)."""

    #: Reader-mode tag for batch provenance contexts (lineage.py).
    lineage_mode = 'arrow'

    def process(self, piece_index, worker_predicate=None,
                shuffle_row_drop_partition=None, pst_det=None):
        from petastorm_tpu.faults import maybe_inject, rowgroup_fault_key

        from petastorm_tpu.trace import get_global_tracer

        piece = self.args['row_groups'][piece_index]
        maybe_inject('decode-corrupt',
                     key=rowgroup_fault_key(piece.path, piece.row_group))
        # Arrow mode ships raw cells, so its 'decode' span covers the
        # columnar table prep (the read span nests inside it) — the same
        # three-span vocabulary as the dict/tensor workers on a merged
        # timeline even though codecs don't run here.
        with get_global_tracer().span('decode', 'worker'):
            table, read_fresh = self._load_table_cached(piece, worker_predicate)
        if table is None or table.num_rows == 0:
            return self._publish_hole(pst_det)

        row_slice = compute_row_slice(table.num_rows, shuffle_row_drop_partition)
        if row_slice is not None:
            start, stop = row_slice
            table = table.slice(start, stop - start)
            if table.num_rows == 0:
                return self._publish_hole(pst_det)

        transform_spec = self.args.get('transform_spec')
        if transform_spec is not None and transform_spec.func is not None:
            table = self._apply_transform(table, transform_spec)

        if table.num_rows and self.args.get('shuffle_rows_in_chunk'):
            # Same session-stable permutation as the tensor path
            # (chunk_row_permutation): decorrelates storage order within
            # the chunk, keeps resume row-skips exact.
            perm = chunk_row_permutation(
                self.args.get('shuffle_seed'), self.args['dataset_path_hash'],
                piece.path, piece.row_group, shuffle_row_drop_partition,
                table.num_rows)
            table = table.take(pa.array(perm))

        if table.num_rows:
            import json as json_mod

            from petastorm_tpu.lineage import chunk_lineage
            # Ventilation key + provenance segment ride in the schema
            # metadata (survives the Arrow IPC serializer) for checkpoint/
            # resume tracking and the batch provenance ledger. Arrow mode
            # ships raw cells, so a cache hit serves the same bytes a read
            # would — the tier distinguishes disk-cache hits from reads.
            md = dict(table.schema.metadata or {})
            md[b'pst.key'] = chunk_key(piece_index, shuffle_row_drop_partition).encode()
            tier = ('decode' if read_fresh
                    else getattr(self.args['cache'], 'lineage_tier', 'cache'))
            lineage = chunk_lineage(
                piece, piece_index, shuffle_row_drop_partition,
                table.num_rows, tier,
                permuted=bool(self.args.get('shuffle_rows_in_chunk')),
                filtered=worker_predicate is not None,
                worker_id=self.worker_id)
            md[b'pst.lineage'] = json_mod.dumps(lineage).encode()
            if pst_det is not None:
                md[b'pst.det'] = json_mod.dumps(pst_det).encode()
            with get_global_tracer().span('handoff', 'worker'):
                self.publish_func(table.replace_schema_metadata(md))
        else:
            self._publish_hole(pst_det)

    def _publish_hole(self, pst_det):
        """Arrow transports serialize tables (never dicts): the sequence-
        hole placeholder is a zero-row, zero-column table whose schema
        metadata carries the ``pst.det`` tag — it survives the IPC
        serializer and the consumer recognizes ``num_rows == 0``."""
        if pst_det is None:
            return
        import json as json_mod
        empty = pa.table({}).replace_schema_metadata(
            {b'pst.det': json_mod.dumps(pst_det).encode()})
        self.publish_func(empty)

    def _apply_transform(self, table, transform_spec):
        """Pandas-based batch transform (parity: ``arrow_reader_worker.py:163-178``)."""
        df = table.to_pandas()
        out = transform_spec.func(df)
        for name in transform_spec.removed_fields:
            if name in out.columns:
                out = out.drop(columns=[name])
        transformed_schema = self.args['transformed_schema']
        keep = [n for n in transformed_schema.fields if n in out.columns]
        return pa.Table.from_pandas(out[keep], preserve_index=False)

    # --- loading ------------------------------------------------------

    def _load_table_cached(self, piece, worker_predicate):
        """``(table, read_fresh)`` — the flag says whether this call paid a
        store read (lineage tier 'decode') or was served by the cache."""
        schema = self.args['schema']
        field_names = list(schema.fields)
        partition_names = set(self.args['partition_names'])
        physical = [n for n in field_names if n not in partition_names]

        if worker_predicate is not None:
            return (self._load_with_predicate(piece, physical, field_names,
                                              worker_predicate), True)

        cache_key = '{}:{}:{}:{}'.format(
            self.args['dataset_path_hash'], piece.path, piece.row_group,
            hashlib.md5(','.join(field_names).encode()).hexdigest()[:8])
        fresh = []

        def load():
            fresh.append(True)
            table = self._read_row_group(piece, physical)
            return self._append_partition_columns(table, piece, field_names)

        return self.args['cache'].get(cache_key, load), bool(fresh)

    def _append_partition_columns(self, table, piece, field_names):
        for name, value in piece.partition_values.items():
            if name in field_names and name not in table.column_names:
                table = table.append_column(
                    name, pa.array([value] * table.num_rows))
        return table

    def _load_with_predicate(self, piece, physical, field_names, predicate):
        """Vectorized two-phase predicate read (parity: ``arrow_reader_worker.py:180-247``)."""
        predicate_fields = sorted(predicate.get_fields())
        full_schema = self.args['full_schema']
        unknown = set(predicate_fields) - set(full_schema.fields)
        if unknown:
            raise ValueError('Predicate uses unknown fields: {}'.format(sorted(unknown)))
        partition_names = set(self.args['partition_names'])
        pred_physical = [n for n in predicate_fields if n not in partition_names]
        pred_table = self._read_row_group(piece, pred_physical)
        pred_table = self._append_partition_columns(pred_table, piece, predicate_fields)
        pred_df = pred_table.to_pandas()
        mask = pred_df.apply(
            lambda r: predicate.do_include({f: r[f] for f in predicate_fields}), axis=1).values \
            if len(pred_df) else np.zeros(0, dtype=bool)
        if not mask.any():
            return None
        other = [n for n in physical if n not in predicate_fields]
        if other:
            other_table = self._read_row_group(piece, other)
            for col in other_table.column_names:
                pred_table = pred_table.append_column(col, other_table.column(col))
        table = self._append_partition_columns(pred_table, piece, field_names)
        keep = [n for n in field_names if n in table.column_names]
        indices = np.flatnonzero(mask)
        return table.select(keep).take(pa.array(indices))


class ArrowResultsQueueReader(DeferredRowAccounting, ResequencedReads):
    """Consumer-side: one Arrow table -> namedtuple of numpy arrays (a batch).

    Parity: reference ``arrow_reader_worker.py:39-79``. Checkpoint
    accounting is chunk-level by default, row-granular after
    ``enable_deferred_rows`` (see ``checkpoint.DeferredRowAccounting``).
    In deterministic mode chunk pops route through the reader's
    resequencer (``ResequencedReads``).
    """

    _last_lineage = None
    _last_det = None

    @property
    def batched_output(self):
        return True

    @property
    def last_chunk_lineage(self):
        """Provenance segment of the most recent chunk (see
        ``TensorResultsQueueReader.last_chunk_lineage``)."""
        return self._last_lineage

    @property
    def last_chunk_det(self):
        """Deterministic-mode tag of the most recent chunk, or None."""
        return self._last_det

    def read_next(self, pool, schema, ngram):
        import json as json_mod
        if ngram is not None:
            raise NotImplementedError('NGram is not supported with batch (Arrow) readers '
                                      '(parity: arrow_reader_worker.py:97-98)')
        while True:
            table = self._pull(pool)
            if table.num_rows == 0:
                # Deterministic-mode sequence-hole placeholder (a worker
                # never publishes a genuinely empty chunk).
                continue
            md = table.schema.metadata or {}
            key = md.get(b'pst.key')
            key = key.decode() if key is not None else None
            lineage = md.get(b'pst.lineage')
            if lineage is not None:
                try:
                    lineage = json_mod.loads(lineage.decode())
                except ValueError:
                    lineage = None
            det = md.get(b'pst.det')
            if det is not None:
                try:
                    det = json_mod.loads(det.decode())
                except ValueError:
                    det = None
            if self._tracker is not None and key is not None:
                skip = self._tracker.on_chunk(key, table.num_rows, det=det)
                if skip:
                    table = table.slice(skip)
                    if lineage is not None:
                        lineage['row_start'] = lineage.get('row_start', 0) + skip
                if table.num_rows == 0:
                    continue
                self._record_chunk(key, table.num_rows)
            self._last_lineage = lineage
            self._last_det = det
            break
        columns = {}
        for name in schema.fields:
            if name not in table.column_names:
                continue
            column = table.column(name)
            columns[name] = _arrow_column_to_numpy(column, schema.fields[name])
        return schema.make_namedtuple(**columns)


def _arrow_column_to_numpy(column, field):
    """Arrow column -> numpy; fixed-length list columns vstack into 2-D arrays.

    Parity: reference ``arrow_reader_worker.py:53-79``.
    """
    if pa.types.is_list(column.type) or pa.types.is_large_list(column.type):
        values = column.to_pylist()
        shapes = {np.shape(v) for v in values if v is not None}
        if len(shapes) == 1 and None not in values:
            return np.vstack([np.asarray(v, dtype=field.numpy_dtype) for v in values]) \
                if len(values) else np.zeros((0,), dtype=field.numpy_dtype)
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = None if v is None else np.asarray(v, dtype=field.numpy_dtype)
        return out
    np_dtype = field.numpy_dtype
    if np_dtype.kind in ('O', 'S', 'U'):
        return column.to_pandas().values
    try:
        # Zero-copy for single-chunk null-free primitives: the numpy array
        # is a read-only view over the Arrow buffer the C++ decode produced
        # (SURVEY §2.9's "Arrow-compatible columnar buffers" leg).
        return column.to_numpy(zero_copy_only=False)
    except (pa.ArrowInvalid, NotImplementedError):
        return column.to_pandas().values
