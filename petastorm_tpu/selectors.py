"""Row-group selectors: choose row-groups via stored indexes.

Parity: reference ``petastorm/selectors.py`` — ``RowGroupSelectorBase``,
``SingleIndexSelector``, plus intersection/union combinators. Selectors
compose over BOTH index granularities: the classic row-group-level
payloads (``SingleFieldIndexer``: value -> ordinals) and the serving
tier's row-level payloads (``SingleFieldRowIndexer``: value ->
``[piece, offset]`` pairs) — :func:`entry_row_groups` normalizes either
entry shape to row-group ordinals.
"""


def entry_row_groups(entries):
    """Row-group ordinals from one index value's entry list: plain ints
    (row-group-level indexes) or ``[piece, row_offset]`` pairs (the
    row-level ``SingleFieldRowIndexer`` payload)."""
    return {entry[0] if isinstance(entry, (list, tuple)) else entry
            for entry in entries}


class RowGroupSelectorBase(object):
    def get_index_names(self):
        raise NotImplementedError

    def select_row_groups(self, indexes):
        """``indexes``: full stored payload ``{index_name: {'values': {...}}}``;
        returns a set of row-group ordinals."""
        raise NotImplementedError


class SingleIndexSelector(RowGroupSelectorBase):
    """Union of row-groups holding any of ``values_list`` in one index."""

    def __init__(self, index_name, values_list):
        self._index_name = index_name
        self._values = list(values_list)

    def get_index_names(self):
        return [self._index_name]

    def select_row_groups(self, indexes):
        if self._index_name not in indexes:
            raise ValueError('Index {!r} not found; available: {}'.format(
                self._index_name, sorted(indexes)))
        value_map = indexes[self._index_name]['values']
        selected = set()
        for value in self._values:
            selected |= entry_row_groups(value_map.get(str(value), ()))
        return selected


class IntersectIndexSelector(RowGroupSelectorBase):
    def __init__(self, selectors):
        self._selectors = list(selectors)

    def get_index_names(self):
        return sorted({n for s in self._selectors for n in s.get_index_names()})

    def select_row_groups(self, indexes):
        result = None
        for selector in self._selectors:
            picked = selector.select_row_groups(indexes)
            result = picked if result is None else (result & picked)
        return result or set()


class UnionIndexSelector(RowGroupSelectorBase):
    def __init__(self, selectors):
        self._selectors = list(selectors)

    def get_index_names(self):
        return sorted({n for s in self._selectors for n in s.get_index_names()})

    def select_row_groups(self, indexes):
        result = set()
        for selector in self._selectors:
            result |= selector.select_row_groups(indexes)
        return result
