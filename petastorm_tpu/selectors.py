"""Row-group selectors: choose row-groups via stored indexes.

Parity: reference ``petastorm/selectors.py`` — ``RowGroupSelectorBase``,
``SingleIndexSelector``, plus intersection/union combinators.
"""


class RowGroupSelectorBase(object):
    def get_index_names(self):
        raise NotImplementedError

    def select_row_groups(self, indexes):
        """``indexes``: full stored payload ``{index_name: {'values': {...}}}``;
        returns a set of row-group ordinals."""
        raise NotImplementedError


class SingleIndexSelector(RowGroupSelectorBase):
    """Union of row-groups holding any of ``values_list`` in one index."""

    def __init__(self, index_name, values_list):
        self._index_name = index_name
        self._values = list(values_list)

    def get_index_names(self):
        return [self._index_name]

    def select_row_groups(self, indexes):
        if self._index_name not in indexes:
            raise ValueError('Index {!r} not found; available: {}'.format(
                self._index_name, sorted(indexes)))
        value_map = indexes[self._index_name]['values']
        selected = set()
        for value in self._values:
            selected.update(value_map.get(str(value), ()))
        return selected


class IntersectIndexSelector(RowGroupSelectorBase):
    def __init__(self, selectors):
        self._selectors = list(selectors)

    def get_index_names(self):
        return sorted({n for s in self._selectors for n in s.get_index_names()})

    def select_row_groups(self, indexes):
        result = None
        for selector in self._selectors:
            picked = selector.select_row_groups(indexes)
            result = picked if result is None else (result & picked)
        return result or set()


class UnionIndexSelector(RowGroupSelectorBase):
    def __init__(self, selectors):
        self._selectors = list(selectors)

    def get_index_names(self):
        return sorted({n for s in self._selectors for n in s.get_index_names()})

    def select_row_groups(self, indexes):
        result = set()
        for selector in self._selectors:
            result |= selector.select_row_groups(indexes)
        return result
