"""Batch provenance ledger + deterministic single-batch replay.

PR 6 gave the pipeline timelines and gauges, but when a training job hits
a NaN at step 41,237 neither can answer the only question that matters:
*which exact rows, decoded by which worker, served from which cache tier,
produced that batch?* The reproducible-pipelines literature (PAPERS.md,
arXiv 2604.21275) argues the input pipeline must make every batch
reconstructible to debug and resume at scale; tf.data (2101.12127) shows
per-element provenance is what turns a data pipeline from a black box
into an auditable system. This module is that layer for petastorm_tpu:

Provenance records
    Every batch that leaves :class:`~petastorm_tpu.jax_loader.JaxLoader`
    gets a compact JSON-safe record: a monotonic ``batch_id``, the
    ordered list of **segments** — ``(parquet file, row-group,
    drop-partition, row-index range)`` spans, each tagged with the
    producing worker (pid/slot) and the serving tier (``decode`` /
    ``chunk-store`` / ``memory`` / ``disk`` / ``remote``) — plus the
    reader's dataset fingerprint, schema hash, shuffle seed and epoch
    order digest, transform-spec version, and an optional per-field
    CRC32 content digest of the staged host batch. Segment metadata is
    attached by the workers at publish time (tensor / arrow / py_dict
    handoff), flows through the results queue (and across the wire for
    :class:`~petastorm_tpu.data_service.RemoteReader`), and is folded
    into batch records by a FIFO :class:`LineageCollector` inside the
    loader's batch assembly.

Ledger
    Records spill to a bounded, crash-tolerant JSONL ledger
    (:class:`LineageLedger`): one header line carrying the reader
    context, then one line per batch, written line-buffered by a
    write-behind thread whose bounded queue DROPS on overflow — batch
    delivery never blocks on disk (``pst_lineage_dropped_total`` counts
    the loss; the ``pst_lineage_ledger_lag`` gauge is the queue depth).
    A SIGKILLed trainer leaves at most one torn trailing line, which
    :func:`read_ledger_file` skips — the same sidecar discipline as the
    PR-6 trace spill. Arm via ``PETASTORM_TPU_LINEAGE_DIR`` or the
    loader's ``lineage=`` knob.

Flight ring
    The last N records live in an in-memory ring; live trackers register
    in a process-wide registry so the stall flight recorder
    (``flight_recorder.py``) can dump ``lineage.json`` next to
    ``trace.json`` on watchdog escalation — the post-mortem then names
    the exact rows in flight when the pipeline died.

Replay
    :func:`replay_record` re-opens the dataset and deterministically
    re-materializes one recorded batch — re-reading exactly the recorded
    row-group spans, re-applying drop-partition slices and the
    session-stable in-chunk permutation, sanitizing dtypes the way the
    loader did — and (in assert mode) verifies the result against the
    record's content digest bit for bit. The
    ``python -m petastorm_tpu.tools.replay`` CLI wraps it.

Determinism contract: replay is exact for pipelines whose per-batch row
composition is itself deterministic given the record — any pool type,
any ``shuffle_row_groups``/``seed``, mid-epoch, process pools included
(the record pins what the shuffle chose). A row-level shuffling buffer
(``shuffling_queue_capacity``), worker predicates, NGrams, or shape
policies make records ``exact: false`` and replay refuses them.
"""

import json
import logging
import os
import queue
import tempfile
import threading
import time
import uuid
import weakref
import zlib
from collections import deque

import numpy as np

logger = logging.getLogger(__name__)

#: Directory that arms ledger spill for every LineageTracker built while
#: it is set (mirrors PETASTORM_TPU_TRACE_DIR / _FLIGHT_RECORDER).
ENV_VAR = 'PETASTORM_TPU_LINEAGE_DIR'

#: Temp-dir prefix for ledgers created without an explicit directory
#: (``lineage=True`` with no env var); the conftest ``lineage`` guard
#: sweeps leaked matches.
TEMP_DIR_PREFIX = 'pst-lineage-'

_HEADER_KEY = '__pst_lineage_ledger__'
LEDGER_GLOB = 'ledger-*.jsonl'

RECORD_VERSION = 1

#: Serving-tier vocabulary (docs + tests assert against these).
TIER_DECODE = 'decode'
TIER_CHUNK_STORE = 'chunk-store'
TIER_MEMORY = 'memory'
TIER_DISK = 'disk'
TIER_REMOTE = 'remote'


def lineage_enabled(explicit=None):
    """Resolve the ``lineage=`` knob against the environment default:
    ``explicit`` wins when not None (a path string or True arms, False
    disarms); otherwise ``PETASTORM_TPU_LINEAGE_DIR`` decides."""
    if explicit is not None:
        return bool(explicit)
    return bool(os.environ.get(ENV_VAR, '').strip())


def resolve_ledger_dir(explicit=None):
    """The ledger directory for an armed tracker: an explicit path wins,
    then the env var, then a fresh ``pst-lineage-*`` temp dir."""
    if isinstance(explicit, str) and explicit:
        return explicit
    env = os.environ.get(ENV_VAR, '').strip()
    if env:
        return env
    return tempfile.mkdtemp(prefix=TEMP_DIR_PREFIX)


def chunk_lineage(piece, piece_index, shuffle_row_drop_partition, n_rows,
                  tier, permuted=False, filtered=False, worker_id=None):
    """The segment metadata a worker attaches to one published chunk.

    Coordinates are *published-chunk-local*: ``row_start`` is the offset
    of the first delivered row within the chunk as published (consumer-
    side resume skips advance it), ``chunk_rows`` is the published
    length — what :func:`replay_record` needs to recompute the in-chunk
    permutation and the drop-partition slice.
    """
    drop = None
    if shuffle_row_drop_partition is not None \
            and shuffle_row_drop_partition[1] > 1:
        drop = [int(shuffle_row_drop_partition[0]),
                int(shuffle_row_drop_partition[1])]
    return {'path': str(piece.path),
            'row_group': int(piece.row_group),
            'piece_index': int(piece_index),
            'drop': drop,
            'chunk_rows': int(n_rows),
            'row_start': 0,
            'worker_pid': os.getpid(),
            'worker_id': worker_id,
            'tier': tier,
            'permuted': bool(permuted),
            'filtered': bool(filtered)}


def _digest_array(arr):
    """CRC32 of an array's bytes (C-order) — fast (~GB/s) and enough to
    prove bit-identity between a live batch and its replay. Object
    columns of bytes (raw image fields on the on-device decode path)
    digest their CONTENTS in order — hashing the object pointers would
    make every run's digest unique."""
    arr = np.asarray(arr)
    if arr.dtype.kind == 'O':
        crc = 0
        for cell in arr.ravel():
            if isinstance(cell, (bytes, bytearray, memoryview)):
                crc = zlib.crc32(cell, crc)
            else:
                crc = zlib.crc32(np.ascontiguousarray(cell), crc)
        return crc & 0xFFFFFFFF
    arr = np.ascontiguousarray(arr)
    return zlib.crc32(arr.view(np.uint8) if arr.dtype.kind in ('M', 'm')
                      else arr) & 0xFFFFFFFF


class LineageCollector(object):
    """FIFO row accounting from delivered chunks to emitted batches.

    The loader's batch assembly consumes reader chunks strictly in
    delivery order (the block fast path slices them FIFO; the per-row
    path without a shuffling buffer appends rows FIFO), so mapping a
    batch back to its source spans is a matter of draining the same FIFO
    here: :meth:`on_chunk` pushes each arriving chunk's segment (with
    its row count), :meth:`on_batch` pops spans covering the batch.

    A row-level shuffling buffer breaks the FIFO property;
    :meth:`mark_inexact` flags every subsequent record ``exact: false``
    (segments then name the contributing chunks, not exact row spans).

    Thread model: all methods are called from the single thread driving
    the host-batch iterator (the staging engine's assemble thread, or
    the consumer under ``prefetch=0``); the pending queue handed to the
    tracker is lock-protected there.
    """

    def __init__(self, tracker, digest=True):
        self._tracker = tracker
        self._digest = digest
        self._fifo = deque()      # [segment dict, consumed offset, remaining]
        self._inexact = False

    def mark_inexact(self):
        self._inexact = True

    def on_chunk(self, segment, n_rows):
        """One reader chunk (or row) arrived. ``segment`` may be None
        (a reader that doesn't attach lineage) — accounting stays exact
        per-row but the record is flagged inexact."""
        if n_rows <= 0:
            return
        if segment is None:
            self._inexact = True
            segment = {'unknown': True, 'row_start': 0,
                       'chunk_rows': int(n_rows)}
        if self._fifo:
            tail = self._fifo[-1]
            if self._coalesces(tail, segment):
                tail[2] += n_rows
                tail[0]['chunk_rows'] = max(
                    tail[0].get('chunk_rows', 0),
                    segment.get('row_start', 0) + n_rows)
                return
        self._fifo.append([dict(segment), 0, int(n_rows)])

    @staticmethod
    def _coalesces(tail, segment):
        """Per-row readers deliver one row at a time; consecutive rows of
        the same chunk merge into one span instead of one segment each."""
        prev = tail[0]
        if prev.get('unknown') or segment.get('unknown'):
            return bool(prev.get('unknown')) and bool(segment.get('unknown'))
        if (prev.get('path') != segment.get('path')
                or prev.get('row_group') != segment.get('row_group')
                or prev.get('drop') != segment.get('drop')):
            return False
        # Contiguity: the new row must extend the uncovered tail exactly.
        return (prev.get('row_start', 0) + tail[1] + tail[2]
                == segment.get('row_start', 0))

    def on_batch(self, n_rows, batch=None, padded=0):
        """A batch of ``n_rows`` source rows (+ ``padded`` repeat-pad
        rows) is being emitted: pop its spans and hand the tracker a
        pending entry (paired FIFO with delivered batches)."""
        segments = []
        need = int(n_rows)
        while need > 0 and self._fifo:
            entry = self._fifo[0]
            segment, offset, remaining = entry
            take = min(need, remaining)
            span = dict(segment)
            base = span.pop('row_start', 0) + offset
            span['row_start'] = base
            span['row_stop'] = base + take
            segments.append(span)
            entry[1] += take
            entry[2] -= take
            if entry[2] == 0:
                self._fifo.popleft()
            need -= take
        exact = not self._inexact and need == 0 \
            and not any(s.get('unknown') or s.get('filtered')
                        for s in segments)
        digest = None
        if self._digest and batch is not None:
            try:
                digest = {name: _digest_array(arr)
                          for name, arr in batch.items()}
            except Exception:  # noqa: BLE001 - advisory, never block a batch
                logger.debug('lineage digest failed', exc_info=True)
        self._tracker._push_pending({
            'rows': int(n_rows) + int(padded),
            'source_rows': int(n_rows),
            'padded': int(padded),
            'segments': segments,
            'exact': exact,
            'fields': sorted(batch) if batch is not None else None,
            'digest': digest})


# Process-wide registry of live trackers: the flight recorder dumps every
# live ring on stall escalation without construction-order coupling.
_live_trackers = weakref.WeakSet()
_live_lock = threading.Lock()


def live_rings():
    """``[{'ctx': ..., 'records': [...], 'in_flight': [...]}]`` for every
    live tracker — what the flight recorder writes to ``lineage.json``.
    ``records`` are delivered batches (newest last); ``in_flight`` are
    batches assembled but never delivered — on a stalled-at-start
    pipeline they are the only provenance there is, and they name the
    exact rows the pipeline died holding."""
    with _live_lock:
        trackers = list(_live_trackers)
    return [{'ctx': t.ctx, 'records': t.ring(),
             'in_flight': t.pending_snapshot()} for t in trackers]


class LineageTracker(object):
    """Owns one pipeline's provenance stream: collector -> pending queue
    -> per-delivery records -> ring + ledger.

    :param ctx: the reader's JSON-safe lineage context
        (:meth:`~petastorm_tpu.reader.Reader.lineage_context`), stored
        once in the ledger header and alongside the ring.
    :param ledger_dir: directory for the JSONL ledger; ``None`` disables
        spill (ring + stats only).
    :param ring_size: records retained for the flight recorder.
    :param digest: compute per-field CRC32 content digests (one fast pass
        per batch; what makes replay's assert mode bit-exact).
    :param state_fn: optional ``() -> dict`` sampled per record (the
        reader's live shuffle state: epoch + order digest).
    :param max_records: ledger line bound — past it records keep landing
        in the ring but the file stops growing (counted as dropped).
    :param queue_size: write-behind queue bound (overflow drops).
    """

    def __init__(self, ctx, ledger_dir=None, ring_size=128, digest=True,
                 state_fn=None, max_records=1000000, queue_size=1024):
        from petastorm_tpu import metrics
        self.ctx = dict(ctx or {})
        self._state_fn = state_fn
        # Sanitizer hookup: lock-order-recorded when PETASTORM_TPU_SANITIZE
        # is armed (name matches pstlint's static graph node).
        from petastorm_tpu.analysis import sanitize
        self._lock = sanitize.tracked_lock(
            'petastorm_tpu.lineage:LineageTracker._lock')
        self._pending = deque()
        self._ring = deque(maxlen=ring_size)
        self._next_batch_id = 0
        self.records = 0
        self.dropped = 0
        self.pressure_dropped = 0   # records shed by the memory governor
        self._pressure_shed = False
        self.collector = LineageCollector(self, digest=digest)
        self._m_records = metrics.counter(
            'pst_lineage_records_total',
            'Batch provenance records committed (ring + ledger)')
        self._m_dropped = metrics.counter(
            'pst_lineage_dropped_total',
            'Provenance records lost (writer queue overflow, ledger line '
            'bound, or batches dropped before delivery)')
        self._ledger = None
        if ledger_dir is not None:
            self._ledger = LineageLedger(ledger_dir, self.ctx,
                                         max_records=max_records,
                                         queue_size=queue_size)
        # Memory-governor accounting (membudget.py): the write-behind
        # queue's records are the only unbounded-ish bytes here (ring and
        # pending are small and bounded); under *degrade* the governor
        # sheds records — counted in pressure_dropped + the dropped
        # metric, never silently.
        from petastorm_tpu import membudget
        self._mem_handle = membudget.register_pool(
            'lineage-queue',
            self.queued_nbytes,
            degrade_fn=lambda: self.set_pressure_shedding(True),
            degrade_release_fn=lambda: self.set_pressure_shedding(False))
        with _live_lock:
            _live_trackers.add(self)

    # -- assemble side (collector calls) -----------------------------------

    def _push_pending(self, entry):
        with self._lock:
            self._pending.append(entry)

    def drop_newest(self):
        """The staging engine dropped the most recently assembled batch
        without delivering it (stop-time race): discard its pending entry
        so the FIFO pairing with delivered batches stays exact."""
        with self._lock:
            if self._pending:
                self._pending.pop()
                self.dropped += 1
        self._m_dropped.inc()

    # -- consumer side -----------------------------------------------------

    def deliver(self):
        """A fresh batch reached the consumer: mint its record (FIFO
        against the assemble side), append to ring + ledger, return it.
        Returns None when no pending entry exists (a reader without
        lineage attached)."""
        with self._lock:
            if not self._pending:
                return None
            entry = self._pending.popleft()
            batch_id = self._next_batch_id
            self._next_batch_id += 1
        record = {'v': RECORD_VERSION,
                  'batch_id': batch_id,
                  'wall_time': time.time(),
                  'pid': os.getpid()}
        record.update(entry)
        if self._state_fn is not None:
            try:
                record['shuffle'] = self._state_fn()
            except Exception:  # noqa: BLE001 - advisory state probe
                logger.debug('lineage state probe failed', exc_info=True)
        with self._lock:
            self._ring.append(record)
            self.records += 1
        self._m_records.inc()
        if self._ledger is not None:
            if self._pressure_shed:
                # Governor degrade rung: the spill is shed — counted, not
                # silent (the ring above still holds the record).
                with self._lock:
                    self.dropped += 1
                    self.pressure_dropped += 1
                self._m_dropped.inc()
            elif not self._ledger.append(record):
                with self._lock:
                    self.dropped += 1
                self._m_dropped.inc()
        return record

    def set_pressure_shedding(self, shed):
        """Memory-governor degrade hook: while True, delivered batches
        still mint ring records (bounded, the post-mortem surface) but the
        ledger spill is SHED — each skipped record counts in
        ``pressure_dropped``/``dropped`` and the dropped metric, never
        silently. Returns True when the flag actually flipped (the
        governor counts transitions, not the per-tick re-asserts)."""
        shed = bool(shed)
        with self._lock:
            changed = shed != self._pressure_shed
            self._pressure_shed = shed
        if changed:
            logger.warning('lineage ledger spill %s under memory pressure',
                           'shed' if shed else 'restored')
        return changed

    def queued_nbytes(self):
        """Estimated bytes parked in the ledger's write-behind queue — the
        memory governor's ``lineage-queue`` accounting hook."""
        if self._ledger is None:
            return 0
        return self._ledger.queued_nbytes()

    def ring(self):
        with self._lock:
            return list(self._ring)

    def pending_snapshot(self):
        """Batches assembled but not yet delivered (no batch_id yet) —
        the in-flight rows a stall post-mortem wants."""
        with self._lock:
            return [dict(e) for e in self._pending]

    @property
    def ledger_path(self):
        return self._ledger.path if self._ledger is not None else None

    def stats(self):
        with self._lock:
            out = {'records': self.records,
                   'dropped': self.dropped,
                   'pressure_dropped': self.pressure_dropped,
                   'pending': len(self._pending),
                   'ring': len(self._ring)}
        if self._ledger is not None:
            # Accepted-then-discarded (write failure) joins accept-time
            # drops: 'dropped' is every record that will never replay.
            out['dropped'] += self._ledger.dropped
            out['ledger_path'] = self._ledger.path
            out['ledger_lag'] = self._ledger.lag
        return out

    def flush(self, timeout_s=5.0):
        if self._ledger is not None:
            return self._ledger.flush(timeout_s)
        return True

    def close(self):
        with _live_lock:
            _live_trackers.discard(self)
        self._mem_handle.close()
        if self._ledger is not None:
            self._ledger.close()


class LineageLedger(object):
    """Bounded, crash-tolerant JSONL spill of provenance records.

    One file per tracker (``ledger-<pid>-<uid>.jsonl``): a header line
    with the reader context, then one line per record, written
    line-buffered by a daemon write-behind thread (named
    ``pst-lineage-writer``) so batch delivery never blocks on disk. The
    bounded queue drops on overflow; ``max_records`` bounds the file.
    A killed process leaves at most one torn trailing line —
    :func:`read_ledger_file` skips it.
    """

    def __init__(self, directory, ctx, max_records=1000000, queue_size=1024):
        from petastorm_tpu import metrics
        self.directory = directory
        self.path = None
        self._max_records = int(max_records)
        self._accepted = 0      # gated synchronously in append()
        self._written = 0
        self._record_bytes_ema = 512.0   # serialized-size estimate (drain)
        self.dropped = 0        # accepted but discarded (write failure/bound)
        self._failed = False
        self._closed = False
        self._file = None
        self._queue = queue.Queue(maxsize=max(1, int(queue_size)))
        # Per-ledger label child (the PR-6 autotune pattern): two armed
        # pipelines in one process must not clobber each other's lag
        # sample, and close() removes the child so a dead ledger's queue
        # object is neither retained nor scraped as live.
        self._label = '{}-{}'.format(os.getpid(), uuid.uuid4().hex[:8])
        self._m_lag = metrics.gauge(
            'pst_lineage_ledger_lag',
            'Provenance records accepted but not yet durable in the '
            'ledger (write-behind queue depth)', labelnames=('ledger',))
        self._m_lag.labels(self._label).set_function(self._queue.qsize)
        self._m_dropped = metrics.counter(
            'pst_lineage_dropped_total',
            'Provenance records lost (writer queue overflow, ledger line '
            'bound, or batches dropped before delivery)')
        try:
            os.makedirs(directory, exist_ok=True)
            self.path = os.path.join(
                directory, 'ledger-{}.jsonl'.format(self._label))
            # buffering=1: one flush per line — complete lines survive a
            # SIGKILL at batch granularity (trace-sidecar discipline).
            self._file = open(self.path, 'w', buffering=1)
            header = {_HEADER_KEY: 1, 'pid': os.getpid(),
                      'wall0': time.time(), 'ctx': ctx}
            self._file.write(json.dumps(header) + '\n')
        except (OSError, TypeError, ValueError):
            logger.warning('cannot open lineage ledger in %r; disabling '
                           'spill', directory, exc_info=True)
            self._failed = True
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name='pst-lineage-writer')
        if not self._failed:
            self._thread.start()

    @property
    def lag(self):
        return self._queue.qsize()

    def queued_nbytes(self):
        """Estimated queued record bytes: depth x the serialized-size EMA
        the drain thread maintains (records are JSON dicts — re-serializing
        them here just to weigh them would double the writer's work)."""
        return int(self._queue.qsize() * self._record_bytes_ema)

    def append(self, record):
        """Enqueue one record for the writer; False when it was dropped
        (ledger closed, writer dead, queue full, or past the line bound).
        The line bound gates at accept time — the async writer must not
        let a burst overshoot the file bound just because its drain lags."""
        if self._failed or self._closed \
                or self._accepted >= self._max_records:
            return False
        try:
            self._queue.put_nowait(record)
            self._accepted += 1
            return True
        except queue.Full:
            return False

    def _drain(self):
        while True:
            record = self._queue.get()
            try:
                if record is None:
                    return
                if self._failed or self._written >= self._max_records:
                    # Accepted (append returned True) yet never durable:
                    # the loss must be counted, not silently consumed —
                    # the 'drops are counted, never silent' contract
                    # covers the write-failure path too.
                    self.dropped += 1
                    self._m_dropped.inc()
                    continue
                try:
                    line = json.dumps(record, default=repr) + '\n'
                    # Size EMA feeds queued_nbytes (governor accounting);
                    # float rebind is atomic, writer thread only.
                    self._record_bytes_ema += 0.2 * (len(line)
                                                     - self._record_bytes_ema)
                    self._file.write(line)
                    self._written += 1
                except (OSError, ValueError):
                    logger.warning('lineage ledger write failed; disabling',
                                   exc_info=True)
                    self._failed = True
                    self.dropped += 1
                    self._m_dropped.inc()
            finally:
                self._queue.task_done()

    def flush(self, timeout_s=5.0):
        """Best-effort drain wait (tests / bench self-checks): True when
        every accepted record reached the file within the timeout. Gates
        on the written count, not the queue depth — the writer pops a
        record (queue hits 0) before its bytes land."""
        deadline = time.monotonic() + timeout_s
        while not self._failed and self._written < self._accepted \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        return not self._failed and self._written >= self._accepted

    def close(self, join_timeout_s=5.0):
        # Refuse new records first (append returns False -> counted as
        # dropped, never silently swallowed by a dead writer); records
        # already accepted still drain before the sentinel lands.
        self._closed = True
        if self._thread.is_alive():
            try:
                self._queue.put(None, timeout=join_timeout_s)
            except queue.Full:
                pass
            self._thread.join(timeout=join_timeout_s)
        # Unbind the lag gauge child: a closed ledger must neither scrape
        # as a live 0 nor keep its queue object reachable via the registry.
        self._m_lag.remove(self._label)
        f, self._file = self._file, None
        if f is not None:
            try:
                f.flush()
                f.close()
            except OSError:  # pragma: no cover - disk already gone
                pass


# --------------------------------------------------------------------------
# ledger reading
# --------------------------------------------------------------------------

def read_ledger_file(path):
    """``(ctx_or_None, [records])`` from one ledger file. Torn trailing
    lines and corrupt lines (a trainer SIGKILLed mid-write) are skipped,
    not fatal — the file stays readable even if its writer died."""
    ctx = None
    records = []
    try:
        with open(path, 'r') as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    continue        # torn/corrupt line: skip, keep reading
                if not isinstance(record, dict):
                    continue
                if record.get(_HEADER_KEY):
                    ctx = record.get('ctx')
                else:
                    records.append(record)
    except OSError:
        logger.warning('cannot read lineage ledger %r', path, exc_info=True)
    return ctx, records


def read_ledger_dir(directory):
    """Every ledger under ``directory`` as ``[(path, ctx, records)]``."""
    import glob
    out = []
    for path in sorted(glob.glob(os.path.join(directory, LEDGER_GLOB))):
        ctx, records = read_ledger_file(path)
        if ctx is not None or records:
            out.append((path, ctx, records))
    return out


def find_record(directory, batch_id, pid=None):
    """Locate one batch record in a ledger directory. Returns
    ``(ctx, record)``; raises ``LookupError`` naming what exists when the
    id is absent or ambiguous (several pipelines ledgered into the same
    directory — disambiguate with ``pid``)."""
    matches = []
    for path, ctx, records in read_ledger_dir(directory):
        for record in records:
            if record.get('batch_id') == batch_id \
                    and (pid is None or record.get('pid') == pid):
                matches.append((path, ctx, record))
    if not matches:
        available = []
        for path, _, records in read_ledger_dir(directory):
            ids = [r.get('batch_id') for r in records]
            if ids:
                available.append('{}: batch ids {}..{} ({} records)'.format(
                    os.path.basename(path), min(ids), max(ids), len(ids)))
        raise LookupError(
            'batch_id {} not found under {!r}. Ledgers present: {}'.format(
                batch_id, directory, '; '.join(available) or 'none'))
    if len(matches) > 1:
        raise LookupError(
            'batch_id {} is ambiguous under {!r} ({} ledgers match — '
            'several pipelines share this directory); pass the producing '
            'pid (candidates: {})'.format(
                batch_id, directory, len(matches),
                sorted({m[2].get('pid') for m in matches})))
    _, ctx, record = matches[0]
    return ctx, record


# --------------------------------------------------------------------------
# replay
# --------------------------------------------------------------------------

class ReplayError(RuntimeError):
    """A record cannot be deterministically re-materialized (inexact
    accounting, unsupported reader mode, or dataset drift)."""


class ReplayMismatchError(ReplayError):
    """Assert-mode replay produced different bytes than the record's
    content digest — the dataset (or decode stack) drifted."""


def _check_replayable(ctx, record):
    if ctx is None:
        raise ReplayError('record has no reader context (ledger header '
                          'missing or torn)')
    if not record.get('exact', False):
        raise ReplayError(
            'record {} is not exact (shuffling buffer, predicate, ngram, '
            'or a reader without lineage attached) — replay would not be '
            'bit-identical'.format(record.get('batch_id')))
    if ctx.get('transform') is not None:
        raise ReplayError(
            'record was produced under a TransformSpec ({}); replay cannot '
            're-run user transform code — re-materialize without it or '
            'replay upstream of the transform'.format(ctx['transform']))
    if ctx.get('shape_policies'):
        raise ReplayError('record was produced under shape policies {}; '
                          'replay cannot reconstruct them'.format(
                              ctx['shape_policies']))
    mode = ctx.get('mode')
    if mode not in ('tensor', 'arrow', 'py_dict', 'mixture'):
        raise ReplayError('unsupported reader mode {!r}'.format(mode))


def _segment_ctx(ctx, segment):
    """The reader context a segment decodes under — for mixtures, the
    source reader's context (segments carry the draw's source index)."""
    if ctx.get('mode') != 'mixture':
        return ctx
    sources = ctx.get('sources') or []
    idx = segment.get('source')
    if idx is None or not 0 <= idx < len(sources):
        raise ReplayError('mixture segment carries no valid source index')
    source_ctx = sources[idx]
    if source_ctx.get('transform') is not None:
        raise ReplayError('mixture source {} was read under a TransformSpec; '
                          'replay cannot re-run user transform code'
                          .format(idx))
    return source_ctx


def _load_segment_table(store, ctx, segment, fields, piece_index):
    """One segment's row-group as a pa.Table restricted to ``fields``,
    partition columns appended — the worker's ``_load_table`` shape.
    ``piece_index`` is the store's ``(path, row_group) -> piece`` map,
    built once per store (a multi-segment batch must not re-list the
    dataset's row groups per segment)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    piece = piece_index.get((str(segment['path']), int(segment['row_group'])))
    if piece is None:
        raise ReplayError(
            'row-group {} of {} no longer exists in the dataset at {} '
            '(dataset drift since the record was written)'.format(
                segment['row_group'], segment['path'], ctx.get('url')))
    from urllib.parse import urlparse
    partition_names = set(store.partition_names)
    physical = [n for n in fields if n not in partition_names]
    # Same handle choice as the workers (rowgroup_worker_base): local
    # stores read via the OS path (memory-mapped), remote via fsspec.
    pf = pq.ParquetFile(str(piece.path), memory_map=True) \
        if urlparse(store.url).scheme == 'file' \
        else pq.ParquetFile(store.open_file(piece.path))
    try:
        table = pf.read_row_group(piece.row_group, columns=physical)
    finally:
        pf.close()
    for name, value in piece.partition_values.items():
        if name in fields and name not in table.column_names:
            table = table.append_column(name, pa.array([value] * table.num_rows))
    return table


def _replay_segment(store, stored_schema, ctx, segment, fields, x64,
                    piece_index):
    """Re-materialize one segment's rows as sanitized column blocks."""
    from petastorm_tpu.jax_loader import _sanitize_array
    from petastorm_tpu.workers.rowgroup_worker_base import (
        chunk_row_permutation, compute_row_slice)

    mode = ctx.get('mode')
    schema_fields = [f for f in ctx.get('fields') or fields
                    if f in stored_schema.fields]
    view = stored_schema.create_schema_view(schema_fields) \
        if schema_fields else stored_schema
    table = _load_segment_table(store, ctx, segment, list(view.fields),
                                piece_index)

    if mode in ('tensor', 'py_dict'):
        cols = _decode_view_to_blocks(table, view, mode)
    else:       # arrow: raw cells, the consumer-side numpy conversion
        from petastorm_tpu.arrow_worker import _arrow_column_to_numpy
        cols = {}
        for name in view.fields:
            if name in table.column_names:
                cols[name] = _arrow_column_to_numpy(
                    table.column(name), view.fields[name])
    n_rows = len(next(iter(cols.values()))) if cols else 0

    drop = segment.get('drop')
    if drop:
        row_slice = compute_row_slice(n_rows, (drop[0], drop[1]))
        if row_slice is not None:
            start, stop = row_slice
            cols = {k: v[start:stop] for k, v in cols.items()}
            n_rows = stop - start
    if segment.get('permuted'):
        perm = chunk_row_permutation(
            ctx.get('seed'), ctx.get('dataset_path_hash'),
            segment['path'], segment['row_group'],
            (drop[0], drop[1]) if drop else None, n_rows)
        cols = {k: v[perm] for k, v in cols.items()}
    if segment.get('chunk_rows') is not None \
            and n_rows != segment['chunk_rows']:
        raise ReplayError(
            'row-group {} of {} now decodes to {} rows; the record says {} '
            '(dataset rewritten in place?)'.format(
                segment['row_group'], segment['path'], n_rows,
                segment['chunk_rows']))
    start, stop = segment['row_start'], segment['row_stop']
    out = {}
    for name in fields:
        if name not in cols:
            raise ReplayError('field {!r} is no longer readable from the '
                              'dataset'.format(name))
        arr = _sanitize_array(np.asarray(cols[name][start:stop]), x64)
        if arr is None:
            raise ReplayError('field {!r} dtype cannot be sanitized the way '
                              'the loader did'.format(name))
        out[name] = arr
    return out


def _decode_view_to_blocks(table, view, mode):
    """Decoded column blocks for tensor/py_dict segments. The tensor path
    reuses the worker's columnar decoder verbatim; the per-row path
    decodes rows then stacks per field — both produce the exact bytes the
    live pipeline fed the loader."""
    if mode == 'tensor':
        from petastorm_tpu.tensor_worker import decode_table_to_blocks
        return decode_table_to_blocks(table, view, decode_threads=1)
    from petastorm_tpu.unischema import decode_rows
    encoded_rows = table.to_pylist()
    rows = decode_rows(encoded_rows, view, num_threads=1)
    cols = {}
    for name in view.fields:
        if rows and name in rows[0]:
            cols[name] = np.asarray([row[name] for row in rows])
    return cols


def replay_record(record, ctx, storage_options=None):
    """Deterministically re-materialize one recorded batch.

    Returns ``{field: np.ndarray}`` with the exact bytes the loader
    staged for that batch (pre-``device_put``). Raises
    :class:`ReplayError` for records outside the determinism contract.
    """
    from petastorm_tpu.etl.dataset_metadata import (get_schema,
                                                    infer_or_load_unischema)
    from petastorm_tpu.storage import ParquetStore

    _check_replayable(ctx, record)
    fields = record.get('fields')
    if not fields:
        raise ReplayError('record carries no field list')
    x64 = bool(ctx.get('x64'))

    stores = {}

    def store_for(seg_ctx):
        url = seg_ctx.get('url')
        if url is None:
            raise ReplayError('segment context carries no dataset url')
        if url not in stores:
            store = ParquetStore(url, storage_options)
            if seg_ctx.get('mode') == 'arrow':
                schema = infer_or_load_unischema(store)
            else:
                schema = get_schema(store)
            piece_index = {(str(p.path), int(p.row_group)): p
                           for p in store.row_groups()}
            stores[url] = (store, schema, piece_index)
        return stores[url]

    parts = []
    for segment in record.get('segments') or []:
        seg_ctx = _segment_ctx(ctx, segment)
        store, stored_schema, piece_index = store_for(seg_ctx)
        parts.append(_replay_segment(store, stored_schema, seg_ctx, segment,
                                     fields, x64, piece_index))
    if not parts:
        raise ReplayError('record {} has no segments'.format(
            record.get('batch_id')))
    batch = {name: (parts[0][name] if len(parts) == 1
                    else np.concatenate([p[name] for p in parts]))
             for name in fields}
    padded = int(record.get('padded') or 0)
    if padded:
        # Repeat-pad the final row, exactly as the loader's 'pad' mode.
        batch = {name: np.concatenate(
            [arr] + [arr[-1:]] * padded) for name, arr in batch.items()}
    rows = int(record.get('rows', 0))
    got = len(next(iter(batch.values())))
    if rows and got != rows:
        raise ReplayError('replay produced {} rows, record says {}'.format(
            got, rows))
    return batch


def verify_record(record, ctx, storage_options=None):
    """Replay + digest assert: returns the replayed batch, raising
    :class:`ReplayMismatchError` if any field's bytes differ from the
    record's CRC32 content digest (records without digests replay but
    cannot be verified — a :class:`ReplayError` says so)."""
    batch = replay_record(record, ctx, storage_options)
    digest = record.get('digest')
    if not digest:
        raise ReplayError(
            'record {} carries no content digest (tracker built with '
            'digest=False); replay succeeded but cannot be verified '
            'bit-identical'.format(record.get('batch_id')))
    bad = []
    for name, arr in batch.items():
        want = digest.get(name)
        have = _digest_array(arr)
        if want is not None and int(want) != have:
            bad.append('{} (recorded {:#010x}, replayed {:#010x})'.format(
                name, int(want), have))
    if bad:
        raise ReplayMismatchError(
            'replayed batch {} differs from the live batch: {}'.format(
                record.get('batch_id'), ', '.join(bad)))
    return batch
