"""WeightedSamplingReader: probability-multiplexed reading from N readers.

Parity: reference ``petastorm/weighted_sampling_reader.py`` — cumsum draw
(``:90-92``), schema/batched/ngram compatibility validation (``:64-77``).

TPU-first improvements: the draw RNG is seedable so every pod host mixes
identically when given the same seed, per-source draw counts ride the
metrics registry (``pst_weighted_reader_draws_total{source=...}`` — the
live mixture-balance signal ROADMAP item 5 needs), and mixture batches
carry provenance: each delivered chunk's lineage segment records which
source reader produced it (``source`` index), so a ledgered batch of a
multi-dataset mixture replays against the right dataset per span
(``petastorm_tpu.lineage``).
"""

import numpy as np


STATE_VERSION = 1


class WeightedSamplingReader(object):
    def __init__(self, readers, probabilities, seed=None, resume_state=None):
        if len(readers) != len(probabilities):
            raise ValueError('readers and probabilities must have equal length')
        if len(readers) < 1:
            raise ValueError('Need at least one reader')
        total = float(sum(probabilities))
        if total <= 0:
            raise ValueError('probabilities must sum to a positive value')
        self._readers = list(readers)
        self._cum = np.cumsum([p / total for p in probabilities])
        self._rng = np.random.default_rng(seed)
        self._seed = seed
        self._last_source = None
        if resume_state is not None:
            # Resumable mixture draws: restoring the RNG stream replays
            # the exact per-source draw sequence the prior session would
            # have continued with — the pst_weighted_reader_draws_total
            # counters then track the same trajectory, making drift after
            # a resume visible as label-series divergence. Source readers
            # are resumed individually (build each with its entry from
            # state['sources'] before passing them here).
            if resume_state.get('version') != STATE_VERSION \
                    or resume_state.get('mode') != 'mixture':
                raise ValueError(
                    'resume_state is not a WeightedSamplingReader state '
                    '(mode={!r})'.format(resume_state.get('mode')))
            if resume_state.get('n_sources') != len(readers):
                raise ValueError(
                    'resume_state captured {} sources; this mixture has {}'
                    .format(resume_state.get('n_sources'), len(readers)))
            self._rng.bit_generator.state = resume_state['rng_state']

        first = readers[0]
        for other in readers[1:]:
            if list(first.transformed_schema.fields) != list(other.transformed_schema.fields):
                raise ValueError('All mixed readers must share the same output schema')
            if first.batched_output != other.batched_output:
                raise ValueError('Cannot mix batched and per-row readers')
            if (first.ngram is None) != (other.ngram is None):
                raise ValueError('Cannot mix ngram and non-ngram readers')
        self.last_row_consumed = False
        # Per-source draw counters (petastorm_tpu.metrics): the scrapable
        # mixture balance — a source starving (or dominating) shows up as
        # label-series drift long before epoch accounting would notice.
        from petastorm_tpu import metrics
        draws = metrics.counter(
            'pst_weighted_reader_draws_total',
            'Samples drawn from each source of a WeightedSamplingReader',
            labelnames=('source',))
        self._m_draws = [draws.labels(str(i)) for i in range(len(readers))]

    @property
    def batched_output(self):
        return self._readers[0].batched_output

    @property
    def ngram(self):
        return self._readers[0].ngram

    @property
    def transformed_schema(self):
        return self._readers[0].transformed_schema

    @property
    def schema(self):
        return self._readers[0].schema

    @property
    def last_chunk_private(self):
        """Block-handoff ownership of the most recent draw, proxied from
        the chosen source (see ``Reader.last_chunk_private``) — without it
        a JaxLoader over a mixture would treat every private chunk as
        cache-shared and copy defensively."""
        if self._last_source is None:
            return False
        return bool(getattr(self._readers[self._last_source],
                            'last_chunk_private', False))

    @property
    def last_chunk_lineage(self):
        """Provenance segment of the most recent draw: the chosen source
        reader's segment plus its ``source`` index (what lets replay pick
        the right dataset context per span)."""
        if self._last_source is None:
            return None
        segment = getattr(self._readers[self._last_source],
                          'last_chunk_lineage', None)
        if segment is None:
            return None
        return dict(segment, source=self._last_source)

    def lineage_context(self):
        """Mixture provenance context: ``mode='mixture'`` wrapping each
        source reader's own context (``sources[i]`` resolves a segment's
        ``source`` index)."""
        sources = []
        for reader in self._readers:
            ctx_fn = getattr(reader, 'lineage_context', None)
            sources.append(ctx_fn() if ctx_fn is not None else {'mode': None})
        return {'mode': 'mixture',
                'seed': self._seed,
                'probabilities': [round(float(p), 6) for p in
                                  np.diff(np.concatenate([[0.0], self._cum]))],
                'sources': sources}

    def lineage_state(self):
        """Per-source live shuffle state (advisory, like the readers')."""
        states = []
        for reader in self._readers:
            state_fn = getattr(reader, 'lineage_state', None)
            states.append(state_fn() if state_fn is not None else None)
        return {'sources': states}

    def __iter__(self):
        return self

    def __next__(self):
        draw = self._rng.random()
        chosen = int(np.searchsorted(self._cum, draw, side='right'))
        chosen = min(chosen, len(self._readers) - 1)
        try:
            row = next(self._readers[chosen])
        except StopIteration:
            self.last_row_consumed = True
            raise
        self._last_source = chosen
        self._m_draws[chosen].inc()
        return row

    next = __next__

    def state_dict(self):
        """Resumable mixture state: the draw RNG (so the per-source draw
        sequence continues identically) plus each source reader's own
        ``state_dict()``. Rebuild each source with its entry from
        ``state['sources']`` and pass the whole dict back as
        ``resume_state=`` to restore the RNG."""
        sources = []
        for reader in self._readers:
            state_fn = getattr(reader, 'state_dict', None)
            sources.append(state_fn() if state_fn is not None else None)
        return {'version': STATE_VERSION, 'mode': 'mixture',
                'n_sources': len(self._readers),
                'rng_state': self._rng.bit_generator.state,
                'sources': sources}

    def stop(self):
        for reader in self._readers:
            reader.stop()

    def join(self):
        for reader in self._readers:
            reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        self.join()
        return False
