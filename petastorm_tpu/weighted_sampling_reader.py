"""WeightedSamplingReader: probability-multiplexed reading from N readers.

Parity: reference ``petastorm/weighted_sampling_reader.py`` — cumsum draw
(``:90-92``), schema/batched/ngram compatibility validation (``:64-77``).

TPU-first improvement: the draw RNG is seedable so every pod host mixes
identically when given the same seed.
"""

import numpy as np


class WeightedSamplingReader(object):
    def __init__(self, readers, probabilities, seed=None):
        if len(readers) != len(probabilities):
            raise ValueError('readers and probabilities must have equal length')
        if len(readers) < 1:
            raise ValueError('Need at least one reader')
        total = float(sum(probabilities))
        if total <= 0:
            raise ValueError('probabilities must sum to a positive value')
        self._readers = list(readers)
        self._cum = np.cumsum([p / total for p in probabilities])
        self._rng = np.random.default_rng(seed)

        first = readers[0]
        for other in readers[1:]:
            if list(first.transformed_schema.fields) != list(other.transformed_schema.fields):
                raise ValueError('All mixed readers must share the same output schema')
            if first.batched_output != other.batched_output:
                raise ValueError('Cannot mix batched and per-row readers')
            if (first.ngram is None) != (other.ngram is None):
                raise ValueError('Cannot mix ngram and non-ngram readers')
        self.last_row_consumed = False

    @property
    def batched_output(self):
        return self._readers[0].batched_output

    @property
    def ngram(self):
        return self._readers[0].ngram

    @property
    def transformed_schema(self):
        return self._readers[0].transformed_schema

    @property
    def schema(self):
        return self._readers[0].schema

    def __iter__(self):
        return self

    def __next__(self):
        draw = self._rng.random()
        chosen = int(np.searchsorted(self._cum, draw, side='right'))
        chosen = min(chosen, len(self._readers) - 1)
        try:
            return next(self._readers[chosen])
        except StopIteration:
            self.last_row_consumed = True
            raise

    next = __next__

    def stop(self):
        for reader in self._readers:
            reader.stop()

    def join(self):
        for reader in self._readers:
            reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        self.join()
        return False
